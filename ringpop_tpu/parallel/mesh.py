"""Device-mesh sharding of the SWIM simulation state.

Layout ("viewer-row" sharding over a 1-D mesh axis ``nodes``):

* every N x N view/buffer tensor is sharded along axis 0 — each chip owns
  the complete *views of* a contiguous block of virtual nodes (all state a
  real node would own locally lives on one chip, like the reference's
  process-per-node ownership, lib/membership.js);
* per-node vectors (``up``, ``responsive``) are replicated — O(N) bools,
  read by arbitrary-index gathers on every step;
* ``adj`` (N x N connectivity) is row-sharded like the views;
* the PRNG key and the tick counter are replicated.

Cross-chip traffic is exactly the simulated network traffic: a probe from
viewer block A to a target on block B is a scatter into another chip's
rows, which XLA lowers to collectives over ICI. This mirrors how the real
cluster's gossip rides the physical network, except the "network" here is
the TPU interconnect. (The reference's TChannel/NCCL-style point-to-point
RPC — SURVEY §5.8 — has no place in an SPMD program; collectives are the
TPU-native equivalent.)

Scaling: one chip's HBM bounds N at roughly sqrt(HBM / ~6 bytes); row
sharding across D chips raises the bound by sqrt(D) at fixed per-chip
memory, which is how the 65k-node BASELINE config is reached on a pod
slice.
"""

from __future__ import annotations

import contextlib
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.models import swim_sim as _sim

from ringpop_tpu.models.swim_delta import (
    DeltaState,
    delta_run_impl,
    delta_step_impl,
)
from ringpop_tpu.models.swim_sim import (
    ClusterState,
    NetState,
    swim_run_impl,
    swim_step_impl,
)

AXIS = "nodes"


def make_mesh(n_devices: int | None = None, devices: Any = None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


def state_sharding(mesh: Mesh, damping: bool = False) -> ClusterState:
    """Pytree of NamedShardings matching ClusterState.  ``damping``
    must match whether the state carries damp tensors (init_state)."""
    row = NamedSharding(mesh, P(AXIS, None))
    rep = NamedSharding(mesh, P())
    return ClusterState(
        view_key=row,
        pb=row,
        suspect_left=row,
        tick=rep,
        damp=row if damping else None,
        damped=row if damping else None,
    )


def net_sharding(mesh: Mesh, like: NetState | None = None) -> NetState:
    """Shardings for ``NetState``; default assumes the healthy network
    (``adj=None``, the ``make_net`` default) — pass ``like=net`` when the
    net carries a materialized adjacency mask."""
    rep = NamedSharding(mesh, P())
    has_adj = like is not None and like.adj is not None
    if not has_adj:
        adj = None
    elif like.adj.ndim == 1:  # group-id vector: O(N), replicate
        adj = rep
    else:
        adj = NamedSharding(mesh, P(AXIS, None))
    return NetState(up=rep, responsive=rep, adj=adj)


def _mesh_recv_merge():
    """Trace-time guard for the dense sharded programs: the Pallas
    receiver-merge lowers to a tpu_custom_call with no SPMD
    partitioning rule, so under RINGPOP_RECV_MERGE="pallas" the mesh
    path falls back to the bit-identical sorted lowering (whose sorts,
    gathers and scatters XLA partitions into collectives).  Applied
    around every jitted call because retraces happen on new input
    signatures, not only the first call."""
    if _sim._recv_merge_form() == "pallas":
        return _sim._force_recv_merge("sorted")
    return contextlib.nullcontext()


def _check_divisible(n: int, mesh: Mesh) -> None:
    d = mesh.devices.size
    if n % d != 0:
        raise ValueError(f"n={n} must be divisible by mesh size {d}")


def shard_cluster(
    state: ClusterState, net: NetState, mesh: Mesh
) -> tuple[ClusterState, NetState]:
    """Place an (unsharded) simulation onto the mesh."""
    _check_divisible(state.n, mesh)
    damping = state.damp is not None
    return (
        jax.device_put(state, state_sharding(mesh, damping)),
        jax.device_put(net, net_sharding(mesh, like=net)),
    )


def sharded_step_jit(
    mesh: Mesh,
    damping: bool = False,
    net_like: NetState | None = None,
    *,
    constrain_outputs: bool = True,
) -> Callable:
    """The raw jitted sharded step — the program ``sharded_step`` wraps
    and the partitioning-contract auditor lowers (analysis/registry.py).

    ``constrain_outputs=False`` drops the explicit ``out_shardings`` so
    XLA's sharding propagation decides the output layout on its own:
    the production wrapper keeps the constraint (a misplaced output is
    a bug the constraint fixes for free), while the auditor checks the
    UNCONSTRAINED propagation — if the row sharding only survives
    because the constraint re-shards it back, a hidden all-gather +
    dynamic-slice pair is paying for every step."""
    rep = NamedSharding(mesh, P())
    return jax.jit(
        swim_step_impl,
        static_argnames=("params",),
        in_shardings=(
            state_sharding(mesh, damping),
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=(
            (state_sharding(mesh, damping), rep) if constrain_outputs else None
        ),
        donate_argnums=(0,),
    )


def sharded_step(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
) -> Callable:
    """``swim_step`` compiled for the mesh: (state, net, key, params) ->
    (state, metrics), state rows pinned to their owning chips.

    Pass ``like=state`` / ``net_like=net`` to infer the damping/adjacency
    layout from the values themselves (a mismatched manual flag fails
    deep inside jit with an opaque pytree-structure error)."""
    if like is not None:
        damping = like.damp is not None
    jitted = sharded_step_jit(mesh, damping, net_like)

    expect_adj = _adj_layout(net_like)

    def step(state, net, key, params):
        _check_adj_layout(net, expect_adj)
        with _mesh_recv_merge():
            return jitted(state, net, key, params)

    return step


def sharded_run(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
) -> Callable:
    """``swim_run`` (lax.scan over ticks) compiled for the mesh.  See
    ``sharded_step`` for ``like``/``net_like``."""
    if like is not None:
        damping = like.damp is not None
    rep = NamedSharding(mesh, P())
    jitted = jax.jit(
        swim_run_impl,
        static_argnames=("params", "ticks"),
        in_shardings=(
            state_sharding(mesh, damping),
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=(state_sharding(mesh, damping), rep),
        donate_argnums=(0,),
    )

    expect_adj = _adj_layout(net_like)

    def run(state, net, key, params, ticks):
        _check_adj_layout(net, expect_adj)
        with _mesh_recv_merge():
            return jitted(state, net, key, params, ticks)

    return run


# ---------------------------------------------------------------------------
# delta backend (models/swim_delta.py): O(N * C) tables, same row ownership
# ---------------------------------------------------------------------------


def delta_state_sharding(
    mesh: Mesh, sided: bool = False, slotbase: bool = False
) -> DeltaState:
    """Shardings for ``DeltaState``: the [N, C] divergence tables are
    viewer-row sharded like the dense views; the shared base and its
    O(N) rank structures are replicated — every viewer's selection and
    merge reads them at arbitrary subject indices, and they change only
    via init/compact/rebase, not inside the step.  ``sided=True``
    covers the structured-netsplit state: the [G, N] base rows and the
    [G, G] flip table replicate, the [N] side vector rides along
    replicated too (each viewer's side is read at gathered indices by
    the routing)."""
    row = NamedSharding(mesh, P(AXIS, None))
    rep = NamedSharding(mesh, P())
    row1 = NamedSharding(mesh, P(AXIS))
    return DeltaState(
        base_key=rep,
        bp_mask=rep,
        bp_rank=rep,
        bp_list=rep,
        d_subj=row,
        d_key=row,
        d_pb=row,
        d_sl=row,
        tick=rep,
        overflow_drops=rep,
        side=rep if sided else None,
        merge_to=rep if sided else None,
        # the rolling digest is per-viewer state like the tables; the
        # full-sync compare gathers h_post[t_safe] cross-shard exactly
        # like the dense step's digest row gather
        digest=row1,
        # per-slot base snapshots (RINGPOP_CARRY_SLOTBASE) ride with
        # their [N, C] tables when the state carries them
        d_bpmask=row if slotbase else None,
        d_bprank=row if slotbase else None,
    )


def shard_delta(state: DeltaState, mesh: Mesh) -> DeltaState:
    """Place an (unsharded) delta state onto the mesh."""
    _check_divisible(state.n, mesh)
    return jax.device_put(
        state,
        delta_state_sharding(
            mesh,
            sided=state.side is not None,
            slotbase=state.d_bpmask is not None,
        ),
    )


def _reject_adjacency(net: NetState) -> None:
    """The sharded delta step takes partitions in the int32[N] group-id
    adjacency form only (replicated across the mesh) — surface a clear
    NotImplementedError for dense bool[N, N] masks at call time, instead
    of the opaque jit pytree/sharding-structure mismatch the adj=None
    in_shardings would otherwise produce."""
    if net.adj is not None and net.adj.ndim != 1:
        raise NotImplementedError(
            "sharded delta partitions take the int32[N] group-id adjacency; "
            "dense bool[N, N] masks need the dense backend"
        )


def sharded_delta_step(
    mesh: Mesh,
    net_like: NetState | None = None,
    state_like: DeltaState | None = None,
) -> Callable:
    """``delta_step`` compiled for the mesh.  The cross-chip traffic is
    the claim routing: the flat (receiver, subject) sort and the
    per-receiver gathers lower to collectives over the row shards —
    the delta analog of the dense scatter-into-foreign-rows.  Pass
    ``net_like=net`` when the net carries a group-id adjacency vector
    (replicated; the only delta partition form)."""
    rep = NamedSharding(mesh, P())
    st_sh = delta_state_sharding(
        mesh, sided=_sided(state_like), slotbase=_slotbase(state_like)
    )
    jitted = jax.jit(
        delta_step_impl,
        static_argnames=("params", "upto"),
        in_shardings=(st_sh, net_sharding(mesh, like=net_like), rep),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,),
    )

    expect_adj = _adj_layout(net_like)

    def step(state, net, key, params, upto=7):
        _reject_adjacency(net)
        _check_adj_layout(net, expect_adj)
        return jitted(state, net, key, params, upto)

    return step


def sharded_delta_run(
    mesh: Mesh,
    net_like: NetState | None = None,
    state_like: DeltaState | None = None,
) -> Callable:
    """``delta_run`` (lax.scan over ticks) compiled for the mesh."""
    rep = NamedSharding(mesh, P())
    st_sh = delta_state_sharding(
        mesh, sided=_sided(state_like), slotbase=_slotbase(state_like)
    )
    jitted = jax.jit(
        delta_run_impl,
        static_argnames=("params", "ticks"),
        in_shardings=(st_sh, net_sharding(mesh, like=net_like), rep),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,),
    )

    expect_adj = _adj_layout(net_like)

    def run(state, net, key, params, ticks):
        _reject_adjacency(net)
        _check_adj_layout(net, expect_adj)
        return jitted(state, net, key, params, ticks)

    return run


def _sided(state_like: DeltaState | None) -> bool:
    return state_like is not None and state_like.side is not None


def _slotbase(state_like: DeltaState | None) -> bool:
    return state_like is not None and state_like.d_bpmask is not None


def _adj_layout(net_like: NetState | None) -> int | None:
    """The adjacency layout a compiled step expects: None (no adj) or
    the adj ndim (1 = group-id vector, 2 = bool mask)."""
    if net_like is None or net_like.adj is None:
        return None
    return net_like.adj.ndim


def _check_adj_layout(net: NetState, expect: int | None) -> None:
    """Clear error when the net's adjacency layout (presence AND ndim)
    disagrees with the compiled in_shardings (built from ``net_like``
    at construction) — otherwise jax.jit fails deep inside with an
    opaque pytree/sharding structure mismatch.  Presence alone is not
    enough: a group-id int32[N] vector and a bool[N, N] mask are both
    "present" but compile to different layouts (Cluster.partition can
    produce either on the dense backend)."""
    have = _adj_layout(net)
    if have == expect:
        return
    names = {None: "no adjacency", 1: "a group-id vector (ndim 1)",
             2: "an adjacency mask (ndim 2)"}
    raise ValueError(
        f"net carries {names.get(have, f'adj ndim {have}')} but this "
        f"sharded step was compiled for {names.get(expect, f'adj ndim {expect}')}"
        " — rebuild with net_like=net"
    )

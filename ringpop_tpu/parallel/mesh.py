"""Device-mesh sharding of the SWIM simulation state.

Layout ("viewer-row" sharding over a 1-D mesh axis ``nodes``):

* every N x N view/buffer tensor is sharded along axis 0 — each chip owns
  the complete *views of* a contiguous block of virtual nodes (all state a
  real node would own locally lives on one chip, like the reference's
  process-per-node ownership, lib/membership.js);
* per-node vectors (``up``, ``responsive``) are replicated — O(N) bools,
  read by arbitrary-index gathers on every step;
* ``adj`` (N x N connectivity) is row-sharded like the views;
* the PRNG key and the tick counter are replicated.

Cross-chip traffic is exactly the simulated network traffic: a probe from
viewer block A to a target on block B is a scatter into another chip's
rows, which XLA lowers to collectives over ICI. This mirrors how the real
cluster's gossip rides the physical network, except the "network" here is
the TPU interconnect. (The reference's TChannel/NCCL-style point-to-point
RPC — SURVEY §5.8 — has no place in an SPMD program; collectives are the
TPU-native equivalent.)

Scaling: one chip's HBM bounds N at roughly sqrt(HBM / ~6 bytes); row
sharding across D chips raises the bound by sqrt(D) at fixed per-chip
memory, which is how the 65k-node BASELINE config is reached on a pod
slice.
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Callable

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.models import swim_sim as _sim
from ringpop_tpu.ops import gossip_remote_copy as _grc

from ringpop_tpu.models.swim_delta import (
    DeltaState,
    delta_run_impl,
    delta_step_impl,
)
from ringpop_tpu.models.swim_sim import (
    ClusterState,
    NetState,
    swim_run_impl,
    swim_step_impl,
)

AXIS = "nodes"


def make_mesh(n_devices: int | None = None, devices: Any = None) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devices)} available"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (AXIS,))


# ---------------------------------------------------------------------------
# Field -> layout maps.  The sharding builders walk the state
# NamedTuple's ``_fields`` against these maps, so a field added to the
# models without a layout decision here raises loudly at build time
# (and tests/test_parallel.py pins the maps complete) instead of
# silently replicating — the PR-15 gap where po_*/pending/pend_* fields
# defaulted to a pytree-mismatching None.
# ---------------------------------------------------------------------------

_ROW = "row"  # P(AXIS, None): viewer-row sharded [N, *] plane
_ROW1 = "row1"  # P(AXIS): per-viewer [N] vector that stays on-shard
_REP = "rep"  # P(): replicated (O(N)/O(K) vectors, scalars)
_ADJ = "adj"  # group-id int32[N] replicated / bool[N, N] row-sharded
_PEND = "pend"  # P(None, AXIS, None): [D, N(receiver), N] claim buffer
_PEND_D = "pend_d"  # P(None, None, AXIS, None): [D, S, N(receiver), W]
_PEND_D1 = "pend_d1"  # P(None, None, AXIS): [D, S, N(receiver)]

CLUSTER_FIELD_SPECS: dict[str, str] = {
    "view_key": _ROW,
    "pb": _ROW,
    "suspect_left": _ROW,
    "tick": _REP,
    "damp": _ROW,
    "damped": _ROW,
    # in-flight claims are receiver-keyed on axis 1 (slot, receiver,
    # subject): each chip owns the claims maturing INTO its rows
    "pending": _PEND,
}

NET_FIELD_SPECS: dict[str, str] = {
    "up": _REP,
    "responsive": _REP,
    "adj": _ADJ,
    # O(K)/O(N) latency, overload and policy vectors: replicated like
    # up/responsive — read at arbitrary gathered indices every step
    "link_src": _REP,
    "link_dst": _REP,
    "link_p": _REP,
    "link_d": _REP,
    "link_j": _REP,
    "period": _REP,
    "ov_cnt": _REP,
    "ov_gray": _REP,
    "po_press": _REP,
    "po_shed": _REP,
    "po_quar": _REP,
    "po_sends_w": _REP,
    "po_deliv_w": _REP,
    "po_retry_cap": _REP,
    # provenance plane residue (obs/provenance.py): O(K x N) report
    # tensors, read host-side only — replicated; the sharded step never
    # updates them (the plane runs in the scenario scan, not the step)
    "pv_slot": _REP,
    "pv_tickv": _REP,
    "pv_wits": _REP,
    "pv_first": _REP,
    "pv_parent": _REP,
    "pv_knows": _REP,
}

DELTA_FIELD_SPECS: dict[str, str] = {
    "base_key": _REP,
    "bp_mask": _REP,
    "bp_rank": _REP,
    "bp_list": _REP,
    "d_subj": _ROW,
    "d_key": _ROW,
    "d_pb": _ROW,
    "d_sl": _ROW,
    "tick": _REP,
    "overflow_drops": _REP,
    "side": _REP,
    "merge_to": _REP,
    # the rolling digest is per-viewer state like the tables; the
    # full-sync compare gathers h_post[t_safe] cross-shard exactly
    # like the dense step's digest row gather
    "digest": _ROW1,
    # per-slot base snapshots (RINGPOP_CARRY_SLOTBASE) ride with
    # their [N, C] tables when the state carries them
    "d_bpmask": _ROW,
    "d_bprank": _ROW,
    # in-flight delta claims: (slot, segment, receiver, width)
    "pend_subj": _PEND_D,
    "pend_key": _PEND_D,
    "pend_recv": _PEND_D1,
}

_SPEC_PARTS = {
    _ROW: P(AXIS, None),
    _ROW1: P(AXIS),
    _REP: P(),
    _PEND: P(None, AXIS, None),
    _PEND_D: P(None, None, AXIS, None),
    _PEND_D1: P(None, None, AXIS),
}


def _field_sharding(
    mesh: Mesh, specs: dict[str, str], field: str, value: Any
) -> NamedSharding | None:
    """The NamedSharding for one state field (None when absent)."""
    if field not in specs:
        raise KeyError(
            f"no sharding layout declared for state field {field!r} — "
            "add it to the FIELD_SPECS map in parallel/mesh.py"
        )
    if value is None:
        return None
    kind = specs[field]
    if kind == _ADJ:
        # group-id vector: O(N), replicate; bool mask: row-shard
        part = P() if value.ndim == 1 else P(AXIS, None)
    else:
        part = _SPEC_PARTS[kind]
    return NamedSharding(mesh, part)


def _tree_sharding(mesh: Mesh, specs: dict[str, str], like: Any) -> Any:
    """Walk ``like``'s NamedTuple fields through the layout map."""
    cls = type(like)
    return cls(
        **{
            f: _field_sharding(mesh, specs, f, getattr(like, f))
            for f in cls._fields
        }
    )


def state_sharding(
    mesh: Mesh,
    damping: bool = False,
    delayed: bool = False,
    *,
    like: ClusterState | None = None,
) -> ClusterState:
    """Pytree of NamedShardings matching ClusterState.  ``damping`` /
    ``delayed`` must match whether the state carries damp tensors and
    the in-flight claim buffer — or pass ``like=state`` to read the
    layout off the value itself."""
    if like is None:
        like = ClusterState(
            view_key=1,
            pb=1,
            suspect_left=1,
            tick=1,
            damp=1 if damping else None,
            damped=1 if damping else None,
            pending=1 if delayed else None,
        )
    return _tree_sharding(mesh, CLUSTER_FIELD_SPECS, like)


def net_sharding(mesh: Mesh, like: NetState | None = None) -> NetState:
    """Shardings for ``NetState``; default assumes the healthy network
    (``adj=None``, the ``make_net`` default) — pass ``like=net`` when the
    net carries adjacency / latency / overload / policy tensors."""
    if like is None:
        like = NetState(up=1, responsive=1)
    return _tree_sharding(mesh, NET_FIELD_SPECS, like)


def _mesh_recv_merge():
    """Trace-time guard for the gather-mode sharded programs: the
    Pallas receiver-merge lowers to a tpu_custom_call with no SPMD
    partitioning rule, so under RINGPOP_RECV_MERGE="pallas" the mesh
    path falls back to the bit-identical sorted lowering (whose sorts,
    gathers and scatters XLA partitions into collectives).  Applied
    around every jitted call because retraces happen on new input
    signatures, not only the first call."""
    if _sim._recv_merge_form() in ("pallas", "ring"):
        return _sim._force_recv_merge("sorted")
    return contextlib.nullcontext()


def gossip_mode(gossip: str | None = None) -> str:
    """Resolve the sharded gossip plane: explicit arg > RINGPOP_GOSSIP
    env > ``ring``.  ``ring`` routes inter-shard claims/acks as
    neighbor-exchange hops (ops/gossip_remote_copy.py) so no member
    plane is ever all-gathered; ``gather`` keeps the PR-15 sorted
    lowering whose row permutation XLA partitions into all-gathers —
    the fallback while a backend/shape combination lacks ring
    coverage."""
    mode = gossip or os.environ.get("RINGPOP_GOSSIP", "ring")
    if mode not in ("ring", "gather"):
        raise ValueError(f"RINGPOP_GOSSIP={mode!r}: ring|gather")
    return mode


@contextlib.contextmanager
def _mesh_gossip(mesh: Mesh, gossip: str | None = None):
    """Trace-time gossip plane for one sharded call: in ring mode an
    ambient ``ring_mesh`` plus the forced ring receiver-merge; in
    gather mode the sorted fallback.  Like the recv-merge knob, the
    mode is baked in at trace time — switching modes on a live
    compiled wrapper needs ``jax.clear_caches()``."""
    if gossip_mode(gossip) == "ring":
        with _grc.ring_mesh(mesh), _sim._force_recv_merge("ring"):
            yield
    else:
        with _mesh_recv_merge():
            yield


def _check_divisible(n: int, mesh: Mesh) -> None:
    d = mesh.devices.size
    if n % d != 0:
        raise ValueError(f"n={n} must be divisible by mesh size {d}")


def shard_cluster(
    state: ClusterState, net: NetState, mesh: Mesh
) -> tuple[ClusterState, NetState]:
    """Place an (unsharded) simulation onto the mesh."""
    _check_divisible(state.n, mesh)
    return (
        jax.device_put(state, state_sharding(mesh, like=state)),
        jax.device_put(net, net_sharding(mesh, like=net)),
    )


def sharded_step_jit(
    mesh: Mesh,
    damping: bool = False,
    net_like: NetState | None = None,
    *,
    delayed: bool = False,
    constrain_outputs: bool = True,
) -> Callable:
    """The raw jitted sharded step — the program ``sharded_step`` wraps
    and the partitioning-contract auditor lowers (analysis/registry.py).

    ``constrain_outputs=False`` drops the explicit ``out_shardings`` so
    XLA's sharding propagation decides the output layout on its own:
    the production wrapper keeps the constraint (a misplaced output is
    a bug the constraint fixes for free), while the auditor checks the
    UNCONSTRAINED propagation — if the row sharding only survives
    because the constraint re-shards it back, a hidden all-gather +
    dynamic-slice pair is paying for every step."""
    rep = NamedSharding(mesh, P())
    st_sh = state_sharding(mesh, damping, delayed)

    # A per-builder function identity: jax's jaxpr trace cache keys on
    # (fun, avals) without shardings, and the ring shard_maps bake the
    # AMBIENT mesh into the jaxpr at trace time — reusing another
    # builder's cached trace would smuggle in its mesh (or its gossip
    # mode).  A fresh closure per builder makes that impossible.
    def _step_impl(state, net, key, params):
        return swim_step_impl(state, net, key, params)

    return jax.jit(
        _step_impl,
        static_argnames=("params",),
        in_shardings=(
            st_sh,
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=((st_sh, rep) if constrain_outputs else None),
        donate_argnums=(0,),
    )


def sharded_step(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
    gossip: str | None = None,
) -> Callable:
    """``swim_step`` compiled for the mesh: (state, net, key, params) ->
    (state, metrics), state rows pinned to their owning chips.

    Pass ``like=state`` / ``net_like=net`` to infer the damping/delay/
    adjacency layout from the values themselves (a mismatched manual
    flag fails deep inside jit with an opaque pytree-structure error).
    ``gossip`` picks the inter-shard plane (see ``gossip_mode``)."""
    delayed = like is not None and like.pending is not None
    if like is not None:
        damping = like.damp is not None
    jitted = sharded_step_jit(mesh, damping, net_like, delayed=delayed)

    expect_adj = _adj_layout(net_like)

    def step(state, net, key, params):
        _check_adj_layout(net, expect_adj)
        with _mesh_gossip(mesh, gossip):
            return jitted(state, net, key, params)

    return step


def sharded_run(
    mesh: Mesh,
    damping: bool = False,
    like: ClusterState | None = None,
    net_like: NetState | None = None,
    gossip: str | None = None,
) -> Callable:
    """``swim_run`` (lax.scan over ticks) compiled for the mesh.  See
    ``sharded_step`` for ``like``/``net_like``/``gossip``."""
    delayed = like is not None and like.pending is not None
    if like is not None:
        damping = like.damp is not None
    rep = NamedSharding(mesh, P())
    st_sh = state_sharding(mesh, damping, delayed)

    def _run_impl(state, net, key, params, ticks):
        return swim_run_impl(state, net, key, params, ticks)

    jitted = jax.jit(
        _run_impl,
        static_argnames=("params", "ticks"),
        in_shardings=(
            st_sh,
            net_sharding(mesh, like=net_like),
            rep,
        ),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,),
    )

    expect_adj = _adj_layout(net_like)

    def run(state, net, key, params, ticks):
        _check_adj_layout(net, expect_adj)
        with _mesh_gossip(mesh, gossip):
            return jitted(state, net, key, params, ticks)

    return run


# ---------------------------------------------------------------------------
# delta backend (models/swim_delta.py): O(N * C) tables, same row ownership
# ---------------------------------------------------------------------------


def delta_state_sharding(
    mesh: Mesh,
    sided: bool = False,
    slotbase: bool = False,
    delayed: bool = False,
    *,
    like: DeltaState | None = None,
) -> DeltaState:
    """Shardings for ``DeltaState``: the [N, C] divergence tables are
    viewer-row sharded like the dense views; the shared base and its
    O(N) rank structures are replicated — every viewer's selection and
    merge reads them at arbitrary subject indices, and they change only
    via init/compact/rebase, not inside the step.  ``sided=True``
    covers the structured-netsplit state: the [G, N] base rows and the
    [G, G] flip table replicate, the [N] side vector rides along
    replicated too (each viewer's side is read at gathered indices by
    the routing).  Pass ``like=state`` to read the layout (sided /
    slotbase / in-flight buffers) off the value itself."""
    if like is None:
        like = DeltaState(
            base_key=1,
            bp_mask=1,
            bp_rank=1,
            bp_list=1,
            d_subj=1,
            d_key=1,
            d_pb=1,
            d_sl=1,
            tick=1,
            overflow_drops=1,
            side=1 if sided else None,
            merge_to=1 if sided else None,
            digest=1,
            d_bpmask=1 if slotbase else None,
            d_bprank=1 if slotbase else None,
            pend_subj=1 if delayed else None,
            pend_key=1 if delayed else None,
            pend_recv=1 if delayed else None,
        )
    return _tree_sharding(mesh, DELTA_FIELD_SPECS, like)


def shard_delta(state: DeltaState, mesh: Mesh) -> DeltaState:
    """Place an (unsharded) delta state onto the mesh."""
    _check_divisible(state.n, mesh)
    return jax.device_put(state, delta_state_sharding(mesh, like=state))


def _reject_adjacency(net: NetState) -> None:
    """The sharded delta step takes partitions in the int32[N] group-id
    adjacency form only (replicated across the mesh) — surface a clear
    NotImplementedError for dense bool[N, N] masks at call time, instead
    of the opaque jit pytree/sharding-structure mismatch the adj=None
    in_shardings would otherwise produce."""
    if net.adj is not None and net.adj.ndim != 1:
        raise NotImplementedError(
            "sharded delta partitions take the int32[N] group-id adjacency; "
            "dense bool[N, N] masks need the dense backend"
        )


def sharded_delta_step_jit(
    mesh: Mesh,
    net_like: NetState | None = None,
    state_like: DeltaState | None = None,
    *,
    constrain_outputs: bool = True,
) -> Callable:
    """The raw jitted sharded delta step — what ``sharded_delta_step``
    wraps and the partitioning-contract auditor lowers.  See
    ``sharded_step_jit`` for ``constrain_outputs``."""
    rep = NamedSharding(mesh, P())
    st_sh = delta_state_sharding(mesh, like=state_like) if (
        state_like is not None
    ) else delta_state_sharding(mesh)

    def _delta_step_impl(state, net, key, params, upto=7):
        return delta_step_impl(state, net, key, params, upto)

    return jax.jit(
        _delta_step_impl,
        static_argnames=("params", "upto"),
        in_shardings=(st_sh, net_sharding(mesh, like=net_like), rep),
        out_shardings=((st_sh, rep) if constrain_outputs else None),
        donate_argnums=(0,),
    )


def sharded_delta_step(
    mesh: Mesh,
    net_like: NetState | None = None,
    state_like: DeltaState | None = None,
    gossip: str | None = None,
) -> Callable:
    """``delta_step`` compiled for the mesh.  The cross-chip traffic is
    the claim routing: in ring mode (default) the routed claim rows hop
    the ring device-to-device; in gather mode the flat (receiver,
    subject) sort and the per-receiver gathers lower to collectives
    over the row shards.  Pass ``net_like=net`` when the net carries a
    group-id adjacency vector (replicated; the only delta partition
    form) and ``state_like=state`` for sided/slotbase/delayed states."""
    jitted = sharded_delta_step_jit(mesh, net_like, state_like)

    expect_adj = _adj_layout(net_like)

    def step(state, net, key, params, upto=7):
        _reject_adjacency(net)
        _check_adj_layout(net, expect_adj)
        with _mesh_gossip(mesh, gossip):
            return jitted(state, net, key, params, upto)

    return step


def sharded_delta_run(
    mesh: Mesh,
    net_like: NetState | None = None,
    state_like: DeltaState | None = None,
    gossip: str | None = None,
) -> Callable:
    """``delta_run`` (lax.scan over ticks) compiled for the mesh."""
    rep = NamedSharding(mesh, P())
    st_sh = delta_state_sharding(mesh, like=state_like) if (
        state_like is not None
    ) else delta_state_sharding(mesh)

    def _delta_run_impl(state, net, key, params, ticks):
        return delta_run_impl(state, net, key, params, ticks)

    jitted = jax.jit(
        _delta_run_impl,
        static_argnames=("params", "ticks"),
        in_shardings=(st_sh, net_sharding(mesh, like=net_like), rep),
        out_shardings=(st_sh, rep),
        donate_argnums=(0,),
    )

    expect_adj = _adj_layout(net_like)

    def run(state, net, key, params, ticks):
        _reject_adjacency(net)
        _check_adj_layout(net, expect_adj)
        with _mesh_gossip(mesh, gossip):
            return jitted(state, net, key, params, ticks)

    return run


# ---------------------------------------------------------------------------
# traffic plane (traffic/engine.py): serve lookups from sharded membership
# ---------------------------------------------------------------------------


def sharded_serve(mesh: Mesh, *, static: Any, gossip: str | None = None) -> Callable:
    """``traffic.engine.serve_tick`` compiled for the mesh: the [N, N]
    view table stays row-sharded and the per-request viewer rows resolve
    over the gossip ring (``ring_fetch_global`` hops) instead of
    all-gathering the membership plane — the traffic plane serves from
    sharded membership truth.  Counters are replicated scalars, exactly
    ``serve_once``'s.  ``gossip`` as in ``gossip_mode``; in gather mode
    the plane replicates like the PR-15 lowering (the bench baseline)."""
    from ringpop_tpu.traffic import engine as _tengine

    rows_sh = NamedSharding(mesh, P(AXIS, None))
    rep = NamedSharding(mesh, P())

    # per-builder identity: see sharded_step_jit
    def _serve(view_rows, up, responsive, tensors, t):
        return _tengine.serve_tick(
            view_rows, up, responsive, tensors, t, static=static
        )

    jitted = jax.jit(_serve, in_shardings=(rows_sh, rep, rep, rep, rep))

    def serve(view_rows, up, responsive, tensors, t):
        view_rows = jax.device_put(view_rows, rows_sh)
        with _mesh_gossip(mesh, gossip):
            return jitted(view_rows, up, responsive, tensors, t)

    return serve


def _sided(state_like: DeltaState | None) -> bool:
    return state_like is not None and state_like.side is not None


def _slotbase(state_like: DeltaState | None) -> bool:
    return state_like is not None and state_like.d_bpmask is not None


def _adj_layout(net_like: NetState | None) -> int | None:
    """The adjacency layout a compiled step expects: None (no adj) or
    the adj ndim (1 = group-id vector, 2 = bool mask)."""
    if net_like is None or net_like.adj is None:
        return None
    return net_like.adj.ndim


def _check_adj_layout(net: NetState, expect: int | None) -> None:
    """Clear error when the net's adjacency layout (presence AND ndim)
    disagrees with the compiled in_shardings (built from ``net_like``
    at construction) — otherwise jax.jit fails deep inside with an
    opaque pytree/sharding structure mismatch.  Presence alone is not
    enough: a group-id int32[N] vector and a bool[N, N] mask are both
    "present" but compile to different layouts (Cluster.partition can
    produce either on the dense backend)."""
    have = _adj_layout(net)
    if have == expect:
        return
    names = {None: "no adjacency", 1: "a group-id vector (ndim 1)",
             2: "an adjacency mask (ndim 2)"}
    raise ValueError(
        f"net carries {names.get(have, f'adj ndim {have}')} but this "
        f"sharded step was compiled for {names.get(expect, f'adj ndim {expect}')}"
        " — rebuild with net_like=net"
    )
